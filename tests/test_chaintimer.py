"""Shared chained-roundtrip timing harness (testing/chaintimer.py), used by
bench.py and the autotuner."""

import numpy as np
import pytest

import jax

from distributedfft_tpu.testing import chaintimer as ct


def test_chain_is_identity_scaled(rng):
    """One roundtrip through the chain reproduces sum|x| (the chain body is
    irfftn(rfftn(x))/N^3 == x up to float error)."""
    shape = (8, 8, 8)
    x = jax.device_put(rng.random(shape).astype(np.float32))
    for k in (1, 3):
        fn = ct.roundtrip_chain(k, shape, "xla")
        got = float(fn(x))
        assert got == pytest.approx(float(np.sum(np.abs(np.asarray(x)))),
                                    rel=1e-4)


def test_median_pair_diff_positive_on_real_work(rng):
    shape = (16, 16, 16)
    x = jax.device_put(rng.random(shape).astype(np.float32))
    fn1 = ct.roundtrip_chain(1, shape, "xla")
    fnK = ct.roundtrip_chain(33, shape, "xla")
    float(fn1(x))
    float(fnK(x))
    per_ms, t1 = ct.median_pair_diff_ms(fn1, fnK, x, 33, repeats=2, inner=2)
    assert per_ms > 0
    assert t1 > 0


def test_k_guard():
    with pytest.raises(ValueError, match="k must be >= 2"):
        ct.median_pair_diff_ms(None, None, None, 1, 1, 1)


class TestDirectionalChain:
    """On-device-input chains (forward / inverse / roundtrip) — how
    north-star sizes and the C2R-only rows are timed through the tunnel."""

    def test_forward_accumulates_serially(self):
        fn1 = ct.directional_chain(1, (16, 16, 16), "matmul", "forward")
        fn5 = ct.directional_chain(5, (16, 16, 16), "matmul", "forward")
        a, b = float(fn1(0)), float(fn5(0))
        # acc grows by ~the same mean-value term per iteration: 5x the
        # 1-chain value up to the 1e-30 perturbation
        assert abs(b - 5 * a) < 1e-3 * abs(b)

    def test_inverse_matches_input_mean(self):
        import numpy as np
        fn1 = ct.directional_chain(1, (16, 16, 16), "xla", "inverse")
        # irfftn(rfftn(u))[0,0,0]/N = u[0,0,0]; one iteration accumulates
        # that single value (bounded, seed-deterministic)
        v = float(fn1(3))
        assert np.isfinite(v) and 0.0 <= v <= 1.0

    def test_roundtrip_direction_matches_external_input_chain(self, rng):
        import jax
        import jax.numpy as jnp
        import numpy as np
        shape = (8, 8, 8)
        internal = float(ct.directional_chain(2, shape, "matmul",
                                              "roundtrip")(5))
        u = np.asarray(jax.jit(lambda: jax.random.uniform(
            jax.random.key(5), shape, jnp.float32))())
        external = float(ct.roundtrip_chain(2, shape, "matmul")(
            jax.device_put(u)))
        assert abs(internal - external) / abs(external) < 1e-5

    def test_bad_direction_rejected(self):
        import pytest as pt
        with pt.raises(ValueError, match="direction"):
            ct.directional_chain(2, (8, 8, 8), "xla", "sideways")


def test_stage_chain_all_stages_run():
    """Each per-axis stage chain compiles and accumulates serially (the
    512^3 per-stage breakdown tool)."""
    import numpy as np
    for stage in ct.STAGES:
        fn1 = ct.stage_chain(1, (8, 8, 8), "matmul", stage)
        fn3 = ct.stage_chain(3, (8, 8, 8), "matmul", stage)
        a, b = float(fn1(0)), float(fn3(0))
        assert np.isfinite(a) and np.isfinite(b), stage
        assert abs(b) >= abs(a) or a == b == 0.0, stage
    import pytest as pt
    with pt.raises(ValueError, match="stage"):
        ct.stage_chain(2, (8, 8, 8), "xla", "fft_w")


def test_direct_max_override_changes_factorization(rng):
    """MXUSettings.direct_max forces four-step on lengths that would run
    direct — the 512-direct vs four-step comparison knob — without
    changing results."""
    import jax
    import numpy as np
    from distributedfft_tpu.ops import fft as lf
    from distributedfft_tpu.ops.mxu_fft import MXUSettings
    x = rng.random((4, 256)).astype(np.float32)
    cx = x.astype(np.complex64)
    st = MXUSettings.make(direct_max=128)  # 256 -> 16x16 four-step
    j_direct = str(jax.make_jaxpr(
        lambda a: lf.fft(a, axis=-1, backend="matmul"))(cx))
    j_split = str(jax.make_jaxpr(
        lambda a: lf.fft(a, axis=-1, backend="matmul", settings=st))(cx))
    assert j_direct != j_split
    a = np.asarray(lf.fft(cx, axis=-1, backend="matmul"))
    b = np.asarray(lf.fft(cx, axis=-1, backend="matmul", settings=st))
    ref = np.fft.fft(x, axis=-1)
    denom = np.abs(ref).max()
    assert np.abs(a - ref).max() / denom < 1e-4
    assert np.abs(b - ref).max() / denom < 1e-4


def test_chunked_forward_chain_accumulates():
    """The chunked-plan forward chain (bench.py's last HBM rung at the
    north-star cube) follows the same serial-accumulator contract as
    directional_chain: k scales the accumulated scalar and the underlying
    chunked transform matches numpy."""
    import numpy as np
    a1 = float(ct.chunked_forward_chain(1, 32, chunk=4)(0))
    a5 = float(ct.chunked_forward_chain(5, 32, chunk=4)(0))
    assert np.isfinite(a1) and np.isfinite(a5)
    # The accumulator adds ~the same mean-derived term per iteration.
    assert abs(a5 - 5 * a1) < 5e-3 * max(1.0, abs(a1) * 5)
