"""Shared chained-roundtrip timing harness (testing/chaintimer.py), used by
bench.py and the autotuner."""

import numpy as np
import pytest

import jax

from distributedfft_tpu.testing import chaintimer as ct


def test_chain_is_identity_scaled(rng):
    """One roundtrip through the chain reproduces sum|x| (the chain body is
    irfftn(rfftn(x))/N^3 == x up to float error)."""
    shape = (8, 8, 8)
    x = jax.device_put(rng.random(shape).astype(np.float32))
    for k in (1, 3):
        fn = ct.roundtrip_chain(k, shape, "xla")
        got = float(fn(x))
        assert got == pytest.approx(float(np.sum(np.abs(np.asarray(x)))),
                                    rel=1e-4)


def test_median_pair_diff_positive_on_real_work(rng):
    shape = (16, 16, 16)
    x = jax.device_put(rng.random(shape).astype(np.float32))
    fn1 = ct.roundtrip_chain(1, shape, "xla")
    fnK = ct.roundtrip_chain(33, shape, "xla")
    float(fn1(x))
    float(fnK(x))
    per_ms, t1 = ct.median_pair_diff_ms(fn1, fnK, x, 33, repeats=2, inner=2)
    assert per_ms > 0
    assert t1 > 0


def test_k_guard():
    with pytest.raises(ValueError, match="k must be >= 2"):
        ct.median_pair_diff_ms(None, None, None, 1, 1, 1)
