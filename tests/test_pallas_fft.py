"""Pallas kernel FFT backend (ops/pallas_fft.py) vs numpy ground truth.

Runs in Pallas interpret mode on the CPU test mesh (compiled Mosaic kernels
need real TPU hardware); covers direct, four-step with the fused twiddle
epilogue, prime fallback, the real-input R2C fast path, norm modes, the f64
fallback route, and an end-to-end slab plan with
``Config(fft_backend="pallas")``.
"""

import numpy as np
import pytest

import distributedfft_tpu as dfft
from distributedfft_tpu.ops import fft as lf
from distributedfft_tpu.ops import pallas_fft
from distributedfft_tpu.params import FFTNorm

pytestmark = pytest.mark.skipif(not pallas_fft.available(),
                                reason="jax build lacks pallas TPU support")


def _rel(a, b):
    return np.max(np.abs(a - b)) / max(np.max(np.abs(b)), 1e-30)


# direct (8, 96), odd direct (12, 13-prime), four-step fused twiddle (1024 ->
# 32x32), non-square four-step (640 -> 20x32).
NS = [8, 12, 13, 96, 640, 1024]


@pytest.mark.parametrize("n", NS)
def test_fft_ifft_vs_numpy(n, rng):
    x = (rng.standard_normal((3, n)) + 1j * rng.standard_normal((3, n))
         ).astype(np.complex64)
    got = np.asarray(pallas_fft.fft(x, axis=-1))
    assert _rel(got, np.fft.fft(x, axis=-1)) < 5e-4
    goti = np.asarray(pallas_fft.ifft(x, axis=-1))
    # FFTNorm.NONE inverse is unnormalized (cuFFT convention).
    assert _rel(goti, n * np.fft.ifft(x, axis=-1)) < 5e-4


@pytest.mark.parametrize("n", NS)
def test_rfft_irfft_vs_numpy(n, rng):
    x = rng.standard_normal((4, n)).astype(np.float32)
    got = np.asarray(pallas_fft.rfft(x, axis=-1))
    ref = np.fft.rfft(x, axis=-1)
    assert got.shape == ref.shape
    assert _rel(got, ref) < 5e-4
    back = np.asarray(pallas_fft.irfft(got, n=n, axis=-1,
                                       norm=FFTNorm.BACKWARD))
    assert _rel(back, x) < 5e-4


def test_four_step_recursion_unfused_branch(rng):
    """n=1042 -> (2, 521): n2 > DIRECT_MAX takes the unfused
    recurse-then-twiddle branch (prime 521 inner stage <= _N_MAX)."""
    n = 1042
    x = rng.standard_normal((2, n)).astype(np.float32)
    got = np.asarray(pallas_fft.rfft(x, axis=-1))
    assert _rel(got, np.fft.rfft(x, axis=-1)) < 2e-3


def test_axis_and_ortho(rng):
    x = rng.standard_normal((5, 32, 7)).astype(np.float32)
    got = np.asarray(pallas_fft.rfft(x, axis=1, norm=FFTNorm.ORTHO))
    assert _rel(got, np.fft.rfft(x, axis=1, norm="ortho")) < 5e-4
    c = x.astype(np.complex64)
    got2 = np.asarray(pallas_fft.ifft(c, axis=0, norm=FFTNorm.ORTHO))
    assert _rel(got2, np.fft.ifft(c, axis=0, norm="ortho")) < 5e-4


def test_f64_falls_back_to_matmul_path(rng):
    """f64 data bypasses the f32-only kernels but must stay correct."""
    x = rng.standard_normal((4, 64)).astype(np.float64)
    got = np.asarray(pallas_fft.rfft(x, axis=-1))
    assert got.dtype == np.complex128
    assert _rel(got, np.fft.rfft(x, axis=-1)) < 1e-11


def test_backend_dispatch_matches_xla(rng):
    x = rng.standard_normal((4, 64)).astype(np.float32)
    a = np.asarray(lf.rfft(x, axis=-1, backend="pallas"))
    b = np.asarray(lf.rfft(x, axis=-1, backend="xla"))
    assert _rel(a, b) < 5e-4


def test_rfftn3d_roundtrip(rng):
    x = rng.standard_normal((8, 8, 8)).astype(np.float32)
    got = np.asarray(pallas_fft.rfftn_3d(x))
    assert _rel(got, np.fft.rfftn(x)) < 5e-4
    back = np.asarray(pallas_fft.irfftn_3d(got, (8, 8, 8)))
    assert _rel(back, x * 8 ** 3) < 5e-4


def test_fused_twiddle_stage_matches_unfused(rng):
    """The fused kernel epilogue must agree with explicit matmul+twiddle."""
    from distributedfft_tpu.ops import mxu_fft as mx
    n1, n2 = 8, 16
    a = (rng.standard_normal((3, n1, n2))
         + 1j * rng.standard_normal((3, n1, n2))).astype(np.complex64)
    fused = np.asarray(pallas_fft._stage(a, mx._dft_np(n2, False, False),
                                         twiddle=(n1, n2, False)))
    unfused = (np.asarray(pallas_fft._stage(a, mx._dft_np(n2, False, False)))
               * mx._twiddle_np(n1, n2, False, False))
    assert _rel(fused, unfused) < 5e-4


def test_slab_plan_with_pallas_backend(devices, rng):
    g = dfft.GlobalSize(16, 16, 16)
    cfg = dfft.Config(fft_backend="pallas")
    mesh = dfft.make_slab_mesh(4, devices)
    plan = dfft.SlabFFTPlan(g, dfft.SlabPartition(4), cfg, mesh=mesh)
    x = rng.standard_normal(g.shape).astype(np.float32)
    out = plan.crop_spectral(plan.exec_r2c(plan.pad_input(x)))
    assert _rel(out, np.fft.rfftn(x)) < 2e-3
    back = plan.crop_real(plan.exec_c2r(plan.exec_r2c(plan.pad_input(x))))
    assert _rel(back, x * g.nx * g.ny * g.nz) < 2e-3
