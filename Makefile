# Repo-level convenience targets. The native C++ layer has its own
# Makefile under native/ (kept separate so `make -C native` stays the
# canonical build there, mirroring the reference's split build).

.PHONY: docs test t1 lint typecheck verify native clean-docs

docs:
	python tools/gendocs.py

test:
	python -m pytest tests/ -x -q

# The ROADMAP tier-1 gate, runnable locally: CPU backend, no slow tests,
# collection errors reported but not fatal (so one broken module cannot
# hide the rest of the suite's state).
t1:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider

# Static lint gate: ruff (config + pinned rule set in pyproject.toml)
# where available; the bench image may not have it, so degrade to
# pyflakes, then to compileall (syntax-only — still catches a broken
# module in every environment). The repo-invariant lints (traced host
# I/O, host-only modules, wisdom flock) run in every environment via the
# in-tree AST linter.
lint:
	python -m compileall -q distributedfft_tpu
	@if python -c "import ruff" 2>/dev/null; then \
	  python -m ruff check distributedfft_tpu; \
	elif python -c "import pyflakes" 2>/dev/null; then \
	  python -m pyflakes distributedfft_tpu; \
	else \
	  echo "ruff/pyflakes not installed; compileall-only lint"; \
	fi
	python -c "from distributedfft_tpu.analysis import srclint; \
	  fs = srclint.lint_repo(); \
	  [print(f) for f in fs]; \
	  raise SystemExit(1 if fs else 0)"

# mypy (config in pyproject.toml: strict on params/wisdom/analysis,
# permissive elsewhere); skipped with a notice where mypy is absent —
# but a mypy that RUNS and finds errors must fail the target.
typecheck:
	@if python -c "import mypy" 2>/dev/null; then \
	  python -m mypy; \
	else \
	  echo "mypy not installed; typecheck skipped"; \
	fi

# The static plan/HLO contract verifier across the rendering matrix on
# an emulated 8-device CPU mesh (see dfft-verify --help for the axes).
verify:
	env JAX_PLATFORMS=cpu python -m distributedfft_tpu.analysis.verify \
	  --emulate-devices 8

native:
	$(MAKE) -C native

clean-docs:
	rm -rf documentation
