# Repo-level convenience targets. The native C++ layer has its own
# Makefile under native/ (kept separate so `make -C native` stays the
# canonical build there, mirroring the reference's split build).

.PHONY: docs test t1 lint native clean-docs

docs:
	python tools/gendocs.py

test:
	python -m pytest tests/ -x -q

# The ROADMAP tier-1 gate, runnable locally: CPU backend, no slow tests,
# collection errors reported but not fatal (so one broken module cannot
# hide the rest of the suite's state).
t1:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider

# Cheap static gate: bytecode-compile everything, then pyflakes when the
# environment has it (the bench/CI image may not; compileall alone still
# catches syntax errors in every module).
lint:
	python -m compileall -q distributedfft_tpu
	@python -c "import pyflakes" 2>/dev/null \
	  && python -m pyflakes distributedfft_tpu \
	  || echo "pyflakes not installed; compileall-only lint"

native:
	$(MAKE) -C native

clean-docs:
	rm -rf documentation
