# Repo-level convenience targets. The native C++ layer has its own
# Makefile under native/ (kept separate so `make -C native` stays the
# canonical build there, mirroring the reference's split build).

.PHONY: docs test native clean-docs

docs:
	python tools/gendocs.py

test:
	python -m pytest tests/ -x -q

native:
	$(MAKE) -C native

clean-docs:
	rm -rf documentation
