#!/usr/bin/env python
"""Job launcher — the analog of the reference's ``launch.py`` (L6).

Reads the same JSON job schema (``size`` sweep, ``global_test_settings``
merged into per-test flags, ``$``-prefixed keys that resist CLI override,
reference ``launch.py:343-347``) and runs each configuration through the
framework's executables. Where the reference shells out
``mpiexec -n <ranks> slab|pencil|reference <flags>`` with generated
host/rank files (``launch.py:230-267``), this launcher spawns
``python -m distributedfft_tpu.cli.<exe> <flags>``: rank count becomes a
mesh-axis size derived from the partition flags (``-p`` / ``-p1``*``-p2``),
and device pinning/affinity is the runtime's job, not a rankfile's.

Usage:
    python launch.py --jobs jobs/tpu/slab/benchmarks_base.json \
        [--global_params "-i 5 -w 2"] [--emulate-devices 8] [--dry-run]
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import subprocess
import sys
from typing import Dict, List


EXES = {"batched": "distributedfft_tpu.cli.batched",
        "pencil": "distributedfft_tpu.cli.pencil",
        "reference": "distributedfft_tpu.cli.reference",
        "slab": "distributedfft_tpu.cli.slab"}


def exe_for_test(test: Dict) -> str:
    name = str(test.get("name", "slab")).lower()
    for key in EXES:
        if key in name:
            return key
    return "slab"


def merge_flags(job: Dict, test: Dict, global_params: Dict[str, str]) -> Dict[str, str]:
    """global_test_settings < test < --global_params, except ``$``-escaped
    keys which survive CLI override (reference launch.py:343-347)."""
    flags: Dict[str, str] = {}
    for src in (job.get("global_test_settings", {}), test):
        for k, v in src.items():
            if k == "name":
                continue
            flags[k.lstrip("$")] = v
    for k, v in global_params.items():
        protected = any(kk.startswith("$") and kk.lstrip("$") == k
                        for src in (job.get("global_test_settings", {}), test)
                        for kk in src)
        if not protected:
            flags[k] = v
    return flags


def flags_to_argv(flags: Dict[str, str]) -> List[str]:
    argv: List[str] = []
    for k, v in flags.items():
        if isinstance(v, bool):
            if v:
                argv.append(k)
        else:
            argv += [k, str(v)]
    return argv


def size_flags(size) -> List[str]:
    if isinstance(size, (list, tuple)):
        nx, ny, nz = size
    else:
        nx = ny = nz = size
    return ["-nx", str(nx), "-ny", str(ny), "-nz", str(nz)]


def parse_param_string(s: str) -> Dict[str, str]:
    toks = shlex.split(s or "")
    out: Dict[str, str] = {}
    i = 0
    while i < len(toks):
        k = toks[i]
        if i + 1 < len(toks) and not toks[i + 1].startswith("-"):
            out[k] = toks[i + 1]
            i += 2
        else:
            out[k] = True
            i += 1
    return out


def run_job(path: str, global_params: Dict[str, str], emulate: int,
            dry_run: bool) -> int:
    with open(path) as f:
        job = json.load(f)
    failures = 0
    for size in job.get("size", []):
        for test in job.get("tests", []):
            flags = merge_flags(job, test, global_params)
            argv = [sys.executable, "-m", EXES[exe_for_test(test)]]
            argv += size_flags(size)
            argv += flags_to_argv(flags)
            if emulate:
                argv += ["--emulate-devices", str(emulate)]
            print("+", " ".join(argv), flush=True)
            if dry_run:
                continue
            rc = subprocess.call(argv)
            if rc != 0:
                print(f"  -> exit {rc}", flush=True)
                failures += 1
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--jobs", nargs="+", required=True,
                    help="job JSON file(s), reference schema")
    ap.add_argument("--global_params", default="",
                    help="extra CLI flags merged into every test "
                         "(overridden by $-escaped job keys)")
    ap.add_argument("--emulate-devices", type=int,
                    default=int(os.environ.get("DFFT_EMULATE_DEVICES", "0")))
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args(argv)
    gp = parse_param_string(args.global_params)
    failures = 0
    for path in args.jobs:
        failures += run_job(path, gp, args.emulate_devices, args.dry_run)
    if failures:
        print(f"{failures} test invocation(s) failed", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
