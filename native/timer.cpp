// Native phase-timer CSV gatherer for distributedfft_tpu.
//
// The reference keeps its benchmark timer native: a C++ `Timer` class stores
// per-phase cumulative-ms markers and appends one CSV block per iteration
// (header row once, then `desc,v0,...,v{P-1},` rows; src/timer.cpp:58-102)
// under a deterministic filename. This file is the TPU framework's native
// rendering of that CSV-append path; Python (utils/timer.py) measures the
// phases — fencing jitted stages with block_until_ready — and hands the
// durations down here via ctypes, with a pure-Python fallback when the lib
// isn't built.
//
// Build: make -C native   (compiled into build/libdfft_planner.so)

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <sys/stat.h>

namespace {

// Decimal string matching Python's repr() byte-for-byte: shortest digit
// string that round-trips, fixed notation for decimal exponents in
// [-4, 16), scientific otherwise — CPython's float_repr_style rules.
// Formatting runs under the "C" numeric locale: a host app may have set a
// locale whose decimal separator is ',' — the CSV delimiter — which would
// corrupt rows and diverge from Python's locale-independent repr().
void format_repr_unlocked(double v, char *buf, size_t cap);

void format_repr(double v, char *buf, size_t cap) {
    static locale_t c_loc = newlocale(LC_NUMERIC_MASK, "C", (locale_t)0);
    locale_t old = c_loc ? uselocale(c_loc) : (locale_t)0;
    format_repr_unlocked(v, buf, cap);
    if (old) uselocale(old);
}

void format_repr_unlocked(double v, char *buf, size_t cap) {
    if (v == 0.0) {
        std::snprintf(buf, cap, std::signbit(v) ? "-0.0" : "0.0");
        return;
    }
    if (std::isnan(v) || std::isinf(v)) {
        std::snprintf(buf, cap, "%g", v);  // "inf"/"-inf"/"nan", like repr
        return;
    }
    int prec = 17;  // significant digits of the shortest round-trip form
    for (int p = 1; p <= 17; ++p) {
        std::snprintf(buf, cap, "%.*g", p, v);
        if (std::strtod(buf, nullptr) == v) { prec = p; break; }
    }
    // Decimal exponent from the %e rendering at that digit count.
    char tmp[64];
    std::snprintf(tmp, sizeof tmp, "%.*e", prec - 1, v);
    const char *ep = std::strchr(tmp, 'e');
    const int e10 = ep ? std::atoi(ep + 1) : 0;
    if (e10 >= 16 || e10 < -4) {
        // %e matches repr's scientific form: sign + >=2-digit exponent,
        // and %.0e of 1e+20 is "1e+20" with no stray point, like repr.
        std::snprintf(buf, cap, "%.*e", prec - 1, v);
        return;
    }
    const int decimals = prec - 1 - e10;
    if (decimals <= 0) {
        // Integral-valued shortest form: repr spells it "123.0".
        std::snprintf(buf, cap, "%.0f.0", v);
        return;
    }
    std::snprintf(buf, cap, "%.*f", decimals, v);
}

}  // namespace

extern "C" {

// Append one iteration block to the Timer CSV at `path`:
//   fresh file:  ",0,1,...,{pcnt-1},"   (header, no trailing newline)
//   every call:  "\n" then one row per section "desc,v,v,...,v,\n"
// with each section's value replicated across the pcnt rank columns
// (single-controller SPMD: one host-side measurement describes all shards).
// The block is formatted in memory and written with a single fwrite so a
// failure cannot leave a partial block for a fallback writer to duplicate.
// Returns 0 on success; 1 on argument error and 2 when the file cannot be
// opened (nothing written — the caller may safely fall back); 3 on a write
// error (file state unknown — the caller must NOT write a fallback block).
namespace {

// Shared body of both entry points; `stride` selects the value layout:
// 0 = one value per section replicated across rank columns, pcnt =
// row-major [n_descs][pcnt] with a distinct value per column.
int append_block(const char *path, const char *const *descs,
                 const double *values, int64_t n_descs, int64_t pcnt,
                 int64_t stride) {
    if (path == nullptr || descs == nullptr || values == nullptr ||
        n_descs < 0 || pcnt <= 0)
        return 1;
    struct stat st;
    const bool fresh = (stat(path, &st) != 0);
    std::string block;
    block.reserve(static_cast<size_t>(n_descs) * (32 + 8 * pcnt) + 64);
    if (fresh) {
        block += ',';
        for (int64_t i = 0; i < pcnt; ++i)
            block += std::to_string(i) + ",";
    }
    block += '\n';
    char buf[64];
    for (int64_t s = 0; s < n_descs; ++s) {
        if (descs[s] == nullptr) return 1;
        block += descs[s];
        block += ',';
        for (int64_t i = 0; i < pcnt; ++i) {
            format_repr(values[stride ? s * stride + i : s], buf, sizeof buf);
            block += buf;
            block += ',';
        }
        block += '\n';
    }
    FILE *f = std::fopen(path, "a");
    if (f == nullptr) return 2;
    const size_t put = std::fwrite(block.data(), 1, block.size(), f);
    const int close_err = std::fclose(f);
    return (put == block.size() && close_err == 0) ? 0 : 3;
}

}  // namespace

int dfft_timer_csv_append(const char *path, const char *const *descs,
                          const double *values, int64_t n_descs,
                          int64_t pcnt) {
    return append_block(path, descs, values, n_descs, pcnt, /*stride=*/0);
}

// Per-rank-column variant: `values` is row-major [n_descs][pcnt] and each
// rank column gets its own value — the multi-controller path, where the
// per-process duration vectors are allgathered (the reference's
// Timer::gather MPI_Gather, src/timer.cpp:58-102) and per-host skew must
// be visible in the CSV instead of process 0's value replicated. Same
// return contract as dfft_timer_csv_append.
int dfft_timer_csv_append_cols(const char *path, const char *const *descs,
                               const double *values, int64_t n_descs,
                               int64_t pcnt) {
    return append_block(path, descs, values, n_descs, pcnt, /*stride=*/pcnt);
}

}  // extern "C"
