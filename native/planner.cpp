// Host-side partition planner for distributedfft_tpu.
//
// The reference computes all partition bookkeeping natively inside its C++
// plan classes: block extents with remainder spread
// (src/slab/default/mpicufft_slab.cpp:112-128), prefix offsets
// (include/params.hpp:58-81 computeOffsets) and per-peer transfer byte
// tables (src/slab/default/mpicufft_slab.cpp:183-229). This library keeps
// that layer native for the TPU framework; Python binds it via ctypes
// (distributedfft_tpu/utils/native_planner.py) with a pure-Python fallback.
//
// Build: make -C native    (produces native/build/libdfft_planner.so)

#include <cstdint>

extern "C" {

// Block distribution of n items over p parts, remainder spread over the
// first parts (reference-compatible). Returns 0 on success.
int dfft_block_sizes(int64_t n, int64_t p, int64_t *out) {
    if (p <= 0 || n < 0 || out == nullptr) return 1;
    const int64_t base = n / p;
    const int64_t rem = n % p;
    for (int64_t i = 0; i < p; ++i) out[i] = base + (i < rem ? 1 : 0);
    return 0;
}

// Exclusive prefix sum -> start offsets (computeOffsets analog).
int dfft_block_starts(const int64_t *sizes, int64_t p, int64_t *out) {
    if (p <= 0 || sizes == nullptr || out == nullptr) return 1;
    int64_t acc = 0;
    for (int64_t i = 0; i < p; ++i) { out[i] = acc; acc += sizes[i]; }
    return 0;
}

// Smallest multiple of p >= n (the XLA even-shard pad target).
int64_t dfft_padded_extent(int64_t n, int64_t p) {
    if (p <= 0) return -1;
    return ((n + p - 1) / p) * p;
}

// Logical per-rank extents under even padded sharding: ceil blocks of the
// padded axis, ranks past the logical extent hold only pad (report 0).
int dfft_even_shard_sizes(int64_t n, int64_t n_pad, int64_t p, int64_t *out) {
    if (p <= 0 || n < 0 || n_pad < n || n_pad % p != 0 || out == nullptr)
        return 1;
    const int64_t b = n_pad / p;
    for (int64_t i = 0; i < p; ++i) {
        int64_t left = n - i * b;
        out[i] = left < 0 ? 0 : (left < b ? left : b);
    }
    return 0;
}

// Bytes moved through one all_to_all global transpose of a padded
// (d0, d1, d2) volume split over p along split_axis: every device exchanges
// its full shard except the diagonal block that stays local — the payload
// the reference tabulates per-peer for Alltoallv
// (src/slab/default/mpicufft_slab.cpp:217-228).
int64_t dfft_transpose_wire_bytes(int64_t d0, int64_t d1, int64_t d2,
                                  int64_t p, int64_t itemsize) {
    if (p <= 0 || itemsize <= 0) return -1;
    const int64_t total = d0 * d1 * d2 * itemsize;
    return total - total / p;  // diagonal block stays on-device
}

}  // extern "C"
